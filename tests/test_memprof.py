"""Memory flight recorder tests (utils/memprof.py + catalog integration).

Covers the ISSUE acceptance criteria directly: an injected leak produces
an attributed report, an injected OOM produces an attributed postmortem
file, per-operator peak attribution sums to the catalog watermark within
1%, and the v6 ``oom_postmortem`` event-log record shape is pinned here
(tests/test_observability.py pins the always-present record set and
points at this file for the OOM-only record).
"""
import glob
import json
import os
import threading

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import DeviceTable, HostTable
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.memory import BufferCatalog, StorageTier
from spark_rapids_tpu.utils.memprof import (MemoryProfiler, get_memprof,
                                            set_memprof)
from spark_rapids_tpu.utils.node_context import node_scope


def _table(n=64, seed=0):
    rng = np.random.default_rng(seed)
    t = pa.table({"a": rng.integers(0, 100, n), "b": rng.uniform(0, 1, n),
                  "s": [f"str{i}" for i in range(n)]})
    return DeviceTable.from_host(HostTable.from_arrow(t), min_bucket=8)


@pytest.fixture
def memprof():
    """Install a fresh profiler for the test, restoring whatever the
    session (sticky configure_memprof) had installed afterwards."""
    prev = get_memprof()
    mp = MemoryProfiler()
    set_memprof(mp)
    yield mp
    set_memprof(prev)


# -- leak detection ----------------------------------------------------------

def test_injected_leak_is_attributed(memprof):
    cat = BufferCatalog(device_limit=1 << 30, host_limit=1 << 30)
    t = _table(seed=1)
    with node_scope(3, "HashAggregateExec", query_id=7):
        leaked = cat.register(t)          # never closed: the leak
        closed = cat.register(_table(seed=2))
        closed.close()                    # properly freed: must NOT flag
    summary = memprof.query_end(7)
    assert summary["leaked_bytes"] == t.nbytes()
    (leak,) = summary["leaked_buffers"]
    assert leak["operator"] == "HashAggregateExec"
    assert leak["node_id"] == 3
    assert leak["bytes"] == t.nbytes()
    assert leak["on_device"] is True
    assert leak["held_s"] >= 0
    assert memprof.leaks_detected == 1
    leaked.close()


def test_clean_query_reports_no_leaks(memprof):
    cat = BufferCatalog(device_limit=1 << 30, host_limit=1 << 30)
    with node_scope(1, "ProjectExec", query_id=9):
        h = cat.register(_table(seed=3))
        h.close()
    summary = memprof.query_end(9)
    assert summary["leaked_bytes"] == 0
    assert summary["leaked_buffers"] == []
    # the aggregation still saw the traffic before being pruned
    op = summary["per_operator"]["ProjectExec#1"]
    assert op["allocs"] == 1 and op["frees"] == 1
    assert op["live_bytes"] == 0 and op["peak_bytes"] > 0


def test_query_end_prunes_aggregation(memprof):
    cat = BufferCatalog(device_limit=1 << 30, host_limit=1 << 30)
    with node_scope(1, "ScanExec", query_id=5):
        cat.register(_table(seed=4)).close()
    assert memprof.query_end(5)["per_operator"]
    # a second scan of the same query id starts clean
    assert memprof.query_end(5)["per_operator"] == {}


# -- per-operator peak attribution ------------------------------------------

def test_per_operator_peaks_sum_to_catalog_watermark(memprof):
    cat = BufferCatalog(device_limit=1 << 30, host_limit=1 << 30)
    handles = []
    for nid, (name, seed) in enumerate([("ScanExec", 10),
                                        ("HashAggregateExec", 11),
                                        ("ShuffleExchangeExec", 12)]):
        with node_scope(nid, name, query_id=11):
            handles.append(cat.register(_table(n=128 * (nid + 1),
                                               seed=seed)))
    wm = cat.watermarks()
    # the peak-holder snapshot sums to the profiler's watermark exactly
    assert sum(memprof.peak_holders.values()) == memprof.peak_bytes
    # and the profiler's watermark matches the catalog's own (1% per the
    # acceptance criteria; exact here — same events drive both)
    assert memprof.peak_bytes == pytest.approx(wm["device_peak_bytes"],
                                               rel=0.01)
    for h in handles:
        h.close()
    summary = memprof.query_end(11)
    per_op_sum = sum(d["peak_bytes"] for d in summary["per_operator"].values())
    # registrations only grew the footprint, so per-operator peaks were
    # all live simultaneously at the global watermark
    assert per_op_sum == pytest.approx(wm["device_peak_bytes"], rel=0.01)
    assert summary["leaked_bytes"] == 0


def test_spill_restore_moves_live_attribution(memprof):
    t1 = _table(seed=20)
    nbytes = t1.nbytes()
    cat = BufferCatalog(device_limit=int(nbytes * 1.5), host_limit=1 << 30)
    with node_scope(0, "ScanExec", query_id=13):
        h1 = cat.register(t1)
        h2 = cat.register(_table(seed=21))
    assert h1.tier == StorageTier.HOST  # pushed down by h2
    # the spilled buffer no longer counts as live device bytes
    assert memprof.live_attributed_bytes == cat.device.used_bytes
    with node_scope(0, "ScanExec", query_id=13):
        h1.get()  # restore (spills h2 back out); churn charged to ScanExec
    assert memprof.live_attributed_bytes == cat.device.used_bytes
    summary = memprof.query_end(13)
    op = summary["per_operator"]["ScanExec#0"]
    assert op["spilled_bytes"] > 0
    assert op["restored_bytes"] > 0
    h1.close()
    h2.close()


# -- OOM postmortem ----------------------------------------------------------

def test_oom_postmortem_file_roundtrip(tmp_path):
    prev = get_memprof()
    mp = MemoryProfiler(report_dir=str(tmp_path))
    set_memprof(mp)
    try:
        conf = RapidsConf({"spark.rapids.tpu.memory.pool.mode": "strict"})
        t = _table(seed=30)
        cat = BufferCatalog(conf, device_limit=16, host_limit=1 << 30)
        with node_scope(2, "BroadcastExec", query_id=17):
            with pytest.raises(MemoryError):
                cat.register(t)
        assert mp.postmortems_written == 1
        (path,) = glob.glob(os.path.join(str(tmp_path), "oom-*.txt"))
        report = open(path, encoding="utf-8").read()
        assert "OOM postmortem" in report
        assert "strict pool mode" in report
        assert "holders by operator" in report
        assert "spill-tier occupancy" in report
        assert "lifecycle events" in report
        assert "semaphore" in report
        assert f"limit={cat.device.limit_bytes}" in report
        (rec,) = mp.drain_postmortems()
        assert rec["path"] == path
        assert rec["context"].startswith("allocation failure")
        assert rec["report"] == report
        assert mp.drain_postmortems() == []  # drained once
    finally:
        set_memprof(prev)


def test_postmortem_ranks_holders_and_replays_ring(tmp_path):
    prev = get_memprof()
    mp = MemoryProfiler(report_dir=str(tmp_path))
    set_memprof(mp)
    try:
        cat = BufferCatalog(device_limit=1 << 30, host_limit=1 << 30)
        with node_scope(1, "BigOp", query_id=19):
            big = cat.register(_table(n=512, seed=31))
        with node_scope(2, "SmallOp", query_id=19):
            small = cat.register(_table(n=32, seed=32))
        rec = mp.oom_postmortem("injected failure", catalog=cat)
        holders = list(rec["holders"])
        assert holders[0] == "q19:BigOp#1"     # ranked: biggest first
        assert "q19:SmallOp#2" in holders
        # the ring replay names both registrations
        assert "op=BigOp" in rec["report"]
        assert "op=SmallOp" in rec["report"]
        big.close()
        small.close()
    finally:
        set_memprof(prev)


# -- two-thread spill-vs-get stress (double-count regression) ----------------

def test_spill_vs_get_two_thread_accounting():
    """SpillableDeviceTable.get() races a concurrent spill pass: before
    the handle held the catalog lock across its acquire/release pair, a
    spill could interleave with the restore's tier flip and double-count
    the buffer's bytes in the device store."""
    prev = get_memprof()
    set_memprof(MemoryProfiler())
    try:
        t1 = _table(seed=40)
        nbytes = t1.nbytes()
        cat = BufferCatalog(device_limit=int(nbytes * 2.5),
                            host_limit=1 << 30)
        h1 = cat.register(t1)
        h2 = cat.register(_table(seed=41))
        stop = threading.Event()
        errors = []

        def getter():
            try:
                while not stop.is_set():
                    h1.get()
            except Exception as e:  # pragma: no cover - failure detail
                errors.append(e)

        def spiller():
            try:
                for _ in range(300):
                    cat.synchronous_spill(nbytes)
            except Exception as e:  # pragma: no cover - failure detail
                errors.append(e)
            finally:
                stop.set()

        threads = [threading.Thread(target=getter),
                   threading.Thread(target=spiller)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)
        assert not errors, errors
        with cat._lock:
            device = sum(s.size_bytes for s in cat._buffers.values()
                         if s.tier == StorageTier.DEVICE)
            host = sum(s.size_bytes for s in cat._buffers.values()
                       if s.tier == StorageTier.HOST)
            assert cat.device.used_bytes == device
            assert cat.host.used_bytes == host
        h1.close()
        h2.close()
        assert cat.device.used_bytes == 0
    finally:
        set_memprof(prev)


# -- snapshots ---------------------------------------------------------------

def test_snapshot_and_stats_shapes(memprof):
    cat = BufferCatalog(device_limit=1 << 30, host_limit=1 << 30)
    with node_scope(4, "SortExec", query_id=23):
        h = cat.register(_table(seed=50))
    snap = memprof.snapshot()
    assert snap["enabled"] is True
    assert snap["live_attributed_bytes"] == cat.device.used_bytes
    assert snap["top_holders"][0]["owner"] == "q23:SortExec#4"
    stats = memprof.stats()
    assert stats["live_buffers"] == 1
    assert stats["operator_live_bytes"] == {"SortExec": h.get().nbytes()}
    h.close()
    assert memprof.snapshot()["live_attributed_bytes"] == 0


def test_unattributed_allocations_still_tracked(memprof):
    cat = BufferCatalog(device_limit=1 << 30, host_limit=1 << 30)
    h = cat.register(_table(seed=51))  # no node_scope active
    snap = memprof.snapshot()
    assert snap["top_holders"][0]["owner"] == "(unattributed)"
    h.close()


# -- event-log schema v6 (OOM-only record + leak replay) ---------------------

def _run_logged_app(tmp_path):
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    from spark_rapids_tpu.session import TpuSession
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 2,
        "spark.rapids.tpu.shuffle.mode": "host",
    })
    rng = np.random.default_rng(7)
    df = sess.create_dataframe(pd.DataFrame({
        "g": rng.integers(0, 5, 400).astype(np.int64),
        "x": rng.normal(size=400)}), num_partitions=2)
    df.group_by("g").agg(f_sum(col("x")).alias("sx")).collect(device=True)
    sess.close()
    (path,) = glob.glob(os.path.join(str(tmp_path), "*.jsonl"))
    return path


def test_eventlog_oom_postmortem_record_keys(tmp_path):
    """The v6 record written on OOM: a postmortem queued in the flight
    recorder is drained into the triggering query's record set with the
    report text stripped (the oom-<ts>.txt file carries it)."""
    prev = get_memprof()
    mp = MemoryProfiler(report_dir=str(tmp_path / "reports"))
    set_memprof(mp)
    try:
        mp.oom_postmortem("injected test OOM")
        path = _run_logged_app(tmp_path / "evt")
        records = [json.loads(line)
                   for line in open(path, encoding="utf-8")]
        (pm,) = [r for r in records if r["event"] == "oom_postmortem"]
        assert set(pm) == {"event", "query_id", "ts", "context", "path",
                           "live_bytes", "peak_bytes", "holders"}
        assert pm["query_id"] == 1
        assert pm["context"] == "injected test OOM"
        assert "report" not in pm  # the file carries the full text

        from spark_rapids_tpu.tools.eventlog import load_event_log
        app = load_event_log(path)
        q = app.query(1)
        assert q.oom_postmortems and \
            q.oom_postmortems[0]["context"] == "injected test OOM"
        assert q.memory_summary is not None
        assert any("OOM postmortem" in w for w in app.health_check())
    finally:
        set_memprof(prev)


def test_health_check_flags_leaked_buffers_from_replay(tmp_path):
    """A v6 memory_summary carrying a leak scan surfaces as a replay
    health warning naming the holding operator."""
    path = str(tmp_path / "app.jsonl")
    records = [
        {"event": "app_start", "ts": 0.0, "app_id": "t", "schema_version": 6,
         "conf": {}},
        {"event": "query_start", "query_id": 1, "ts": 1.0, "plan": "",
         "trace_id": ""},
        {"event": "memory_summary", "query_id": 1, "ts": 2.0, "summary": {
            "query_id": 1, "peak_bytes": 4096,
            "peak_holders": {"q1:ScanExec#0": 4096},
            "per_operator": {},
            "leaked_buffers": [{"buffer": 5, "bytes": 2048,
                                "operator": "ScanExec", "node_id": 0,
                                "on_device": True, "held_s": 1.0}],
            "leaked_bytes": 2048}},
        {"event": "query_end", "query_id": 1, "ts": 2.0, "wall_s": 1.0},
        {"event": "app_end", "ts": 3.0},
    ]
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    from spark_rapids_tpu.tools.eventlog import load_event_log
    app = load_event_log(path)
    warnings = app.health_check()
    assert any("2048 bytes leaked" in w and "ScanExec" in w
               for w in warnings), warnings
