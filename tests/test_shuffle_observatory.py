"""Shuffle & collective observatory: per-tier transfer telemetry (ISSUE 19).

Covers the acceptance contract:
- zero overhead when off: every hook compiles down to a single
  module-constant check (bytecode pin, the utils/movement.py pattern)
  and the v12 record's payload is null,
- forensics ring is bounded while the per-(query, shuffle, tier)
  aggregation stays exact,
- sender/receiver stitching over real TCP: the SRTC traced wire header
  pairs the client's recv wall with the server's serve wall for the
  same block,
- straggler attribution: slowest-partition wall vs p50 with the worst
  (shuffle, partition, tier) triple,
- TPC-H end to end (q3/q5): every query's event log carries a v12
  ``shuffle_summary`` whose tier enqueue bytes reconcile EXACTLY with
  the summed ``shuffleBytes`` operator metric,
- the surfacing round-trips: health_check straggler/backpressure
  warnings, diagnose.py findings, compare.py's shuffle-wall/wire-bytes
  gate and the history sentinel's shuffle-wall gate.

Process-wide observatory state is drained between modules by the
conftest ``_drain_shuffle_observatory_per_module`` fixture.
"""
import glob
import json
import os

import pytest

from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.shuffle import telemetry


@pytest.fixture
def observatory():
    """A fresh process-wide observatory; cleared afterwards so the
    module leaves the default (off) state behind."""
    obs = telemetry.configure_shuffle_telemetry(RapidsConf(
        {"spark.rapids.tpu.shuffle.telemetry.enabled": True}))
    yield obs
    telemetry.reset_shuffle_telemetry()


# ---------------------------------------------------------------------------
# zero overhead when off
# ---------------------------------------------------------------------------
def test_zero_overhead_when_off_bytecode_pin():
    """Off is the default; every hook's FIRST action must be the
    module-constant is-None check — co_names[0] pins that no other
    global (let alone a conf lookup) is touched before the early
    return (the utils/movement.py cost-model pattern)."""
    telemetry.reset_shuffle_telemetry()
    for fn in (telemetry.clock, telemetry.note_transfer):
        assert fn.__code__.co_names[0] == "_OBSERVATORY", fn.__name__
    assert telemetry.active() is None
    # and the disabled path records nothing / returns the null payload
    telemetry.note_transfer("ici", "dispatch", shuffle_id=0,
                            logical_bytes=lambda: 1 / 0)  # never called
    assert telemetry.clock() == 0.0
    assert telemetry.drain_ring() == []
    assert telemetry.query_summary(0) is None


def test_conf_off_means_no_observatory():
    assert telemetry.configure_shuffle_telemetry(RapidsConf({})) is None
    assert telemetry.active() is None


# ---------------------------------------------------------------------------
# ring bound vs exact aggregation
# ---------------------------------------------------------------------------
def test_ring_bounded_aggregation_exact():
    obs = telemetry.configure_shuffle_telemetry(RapidsConf({
        "spark.rapids.tpu.shuffle.telemetry.enabled": True,
        "spark.rapids.tpu.shuffle.telemetry.ringSize": 16,
    }))
    try:
        for i in range(100):
            obs.note("local", "enqueue", shuffle_id=1, partition=i % 4,
                     logical_bytes=10, query_id=7)
        ring = obs.drain_ring()
        assert len(ring) == 16          # oldest dropped
        t = obs.totals()
        assert t["transfers"] == 100    # aggregation exact regardless
        assert t["logical_bytes"] == 1000
        s = obs.query_summary(7)
        assert s["totals"]["transfers"] == 100
        (tier,) = s["tiers"]
        assert tier["tier"] == "local" and tier["count"] == 100
    finally:
        telemetry.reset_shuffle_telemetry()


# ---------------------------------------------------------------------------
# TCP sender/receiver stitching (real sockets, SRTC traced header)
# ---------------------------------------------------------------------------
def test_tcp_stitches_sender_and_receiver_halves(observatory):
    from spark_rapids_tpu.shuffle.serializer import serialize_table
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport
    from spark_rapids_tpu.shuffle.transport import BlockId
    from spark_rapids_tpu.utils.tracing import (TraceContext,
                                                activate_trace_context)
    from spark_rapids_tpu.columnar.host import HostColumn, HostTable
    from spark_rapids_tpu.columnar import dtypes as dt
    import numpy as np

    table = HostTable(["v"], [
        HostColumn(dt.LONG, np.arange(32, dtype=np.int64))])
    a = TcpShuffleTransport()
    b = TcpShuffleTransport()
    try:
        b.add_peer(*a.address)
        a.publish(BlockId(3, 1, 2), serialize_table(table))
        ctx = TraceContext("0123456789abcdef", 1, query_id=42)
        with activate_trace_context(ctx):
            got = dict(b.fetch([BlockId(3, 1, 2)]))
        assert BlockId(3, 1, 2) in got
        stitched = observatory.stitched()
        assert stitched, "no sender/receiver pair stitched"
        (pair,) = [s for s in stitched if s["shuffle_id"] == 3]
        assert pair["trace_id"] == "0123456789abcdef"
        assert pair["map_id"] == 1 and pair["partition"] == 2
        assert pair["send_bytes"] > 0 and pair["recv_bytes"] > 0
        assert pair["send_wall_s"] >= 0 and pair["recv_wall_s"] >= 0
        # both halves attribute to the traced query
        assert observatory.query_summary(42)["totals"]["stitched"] >= 1
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# straggler attribution
# ---------------------------------------------------------------------------
def test_straggler_slowest_partition_vs_p50(observatory):
    import time as _time

    def note_wall(partition, wall):
        observatory.note("transport", "fetch", shuffle_id=9,
                         partition=partition,
                         t0=_time.perf_counter() - wall, query_id=5)

    for p, wall in ((0, 0.01), (1, 0.01), (2, 0.012), (3, 0.1)):
        note_wall(p, wall)
    st = observatory.query_summary(5)["straggler"]
    assert st is not None
    assert st["worst"] == {"shuffle_id": 9, "partition": 3,
                           "tier": "transport",
                           "wall_s": pytest.approx(st["slowest_wall_s"])}
    assert st["slowest_wall_s"] == pytest.approx(0.1, rel=0.3)
    assert st["skew"] == pytest.approx(
        st["slowest_wall_s"] / st["p50_wall_s"])
    assert st["skew"] > 4


# ---------------------------------------------------------------------------
# TPC-H end to end: v12 records + metric reconciliation
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tpch_app(tmp_path_factory):
    """q3/q5 under the observatory + event log, replayed."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools import tpch
    from spark_rapids_tpu.tools.eventlog import load_event_log
    logdir = str(tmp_path_factory.mktemp("shuffle_evl"))
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": logdir,
        "spark.rapids.tpu.shuffle.telemetry.enabled": True,
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 4,
    })
    tables = tpch.gen_all(0, tiny=True)
    dfs = tpch.build_dataframes(sess, tables, num_partitions=2)
    for name in ("q3", "q5"):
        getattr(tpch, name)(dfs).collect(device=True)
    sess.close()
    telemetry.reset_shuffle_telemetry()
    (path,) = glob.glob(os.path.join(logdir, "*.jsonl"))
    records = [json.loads(line) for line in open(path, encoding="utf-8")]
    return load_event_log(path), records


def test_tpch_every_query_carries_v12_shuffle_summary(tpch_app):
    app, _records = tpch_app
    assert len(app.queries) == 2
    for q in app.queries.values():
        sh = q.shuffle_summary
        assert sh is not None, f"q{q.query_id} shuffle_summary missing"
        t = sh["totals"]
        assert t["transfers"] > 0 and t["logical_bytes"] > 0
        assert sh["tiers"] and sh["shuffles"]
        for tier in sh["tiers"]:
            assert tier["tier"] in telemetry.TIERS, tier["tier"]


def test_tpch_tier_bytes_reconcile_with_shuffle_bytes_metric(tpch_app):
    """The acceptance pin: each query's shuffle_summary tier logical
    bytes sum EXACTLY to the summed ``shuffleBytes`` operator metric —
    the observatory's enqueue notes mirror every metrics.add() at the
    exchange chokepoints, so the two ledgers cannot drift."""
    app, _records = tpch_app
    for q in app.queries.values():
        metric = sum(n.get("metrics", {}).get("shuffleBytes", 0)
                     for n in q.nodes)
        assert metric > 0, f"q{q.query_id} moved no shuffle bytes"
        tier_bytes = sum(t["logical_bytes"]
                         for t in q.shuffle_summary["tiers"])
        assert tier_bytes == metric, (
            f"q{q.query_id}: observatory {tier_bytes}B != "
            f"shuffleBytes metric {metric}B")


def test_v12_record_shape(tpch_app):
    """Record-shape pin: ONE shuffle_summary per query with the stable
    key set; the payload's totals carry exactly the documented keys."""
    _app, records = tpch_app
    recs = [r for r in records if r["event"] == "shuffle_summary"]
    assert len(recs) == 2
    for r in recs:
        assert set(r) == {"event", "query_id", "ts", "shuffle"}
        sh = r["shuffle"]
        assert set(sh) == {"totals", "tiers", "shuffles", "straggler"}
        assert set(sh["totals"]) == set(telemetry.TOTAL_KEYS) \
            | {"wall_s", "max_queue_depth"}
        for tier in sh["tiers"]:
            assert {"tier", "count", "logical_bytes", "wire_bytes",
                    "wall_s", "retries", "max_queue_depth",
                    "phases"} <= set(tier)


def test_diagnose_carries_shuffle_summary(tpch_app):
    from spark_rapids_tpu.tools.diagnose import diagnose_app
    app, _records = tpch_app
    report = diagnose_app(app)
    for qd in report.queries:
        assert qd.shuffle is not None
        assert qd.shuffle["totals"]["transfers"] > 0


# ---------------------------------------------------------------------------
# surfacing round-trips on synthetic v12 logs
# ---------------------------------------------------------------------------
def _summary(wall=0.2, wire=4 << 20, retries=0, skew=1.0, depth=0):
    slowest = 0.1 * skew
    return {
        "totals": {"transfers": 8, "logical_bytes": wire,
                   "wire_bytes": wire, "retries": retries, "stitched": 0,
                   "wall_s": wall, "max_queue_depth": depth},
        "tiers": [{"tier": "transport", "count": 8,
                   "logical_bytes": wire, "wire_bytes": wire,
                   "wall_s": wall, "retries": retries,
                   "max_queue_depth": depth,
                   "phases": {"fetch": wall}}],
        "shuffles": [{"shuffle_id": 1, "tier": "transport", "count": 8,
                      "logical_bytes": wire, "wire_bytes": wire,
                      "wall_s": wall, "retries": retries,
                      "max_queue_depth": depth}],
        "straggler": {"slowest_wall_s": slowest, "p50_wall_s": 0.1,
                      "skew": skew,
                      "worst": {"shuffle_id": 1, "partition": 3,
                                "tier": "transport",
                                "wall_s": slowest}} if skew > 1 else None,
    }


def _v12_log(path, app_id, shuffle, stats=None):
    recs = [
        {"event": "app_start", "app_id": app_id, "schema_version": 12,
         "ts": 0.0, "conf": {}},
        {"event": "query_start", "query_id": 0, "ts": 1.0, "plan": "p",
         "trace_id": "t"},
        {"event": "shuffle_summary", "query_id": 0, "ts": 2.0,
         "shuffle": shuffle},
        {"event": "query_end", "query_id": 0, "ts": 2.0, "wall_s": 1.0,
         "final_plan": "p", "aqe_events": [], "spill_count": {},
         "semaphore_wait_s": 0.0, "stats": stats or {}, "trace_id": "t",
         "critical_path": None},
        {"event": "app_end", "ts": 3.0},
    ]
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    return str(path)


def test_health_check_warns_on_straggler_and_retries(tmp_path):
    from spark_rapids_tpu.tools.eventlog import load_event_log
    app = load_event_log(_v12_log(
        tmp_path / "sh.jsonl", "sh",
        _summary(retries=3, skew=8.0, depth=6)))
    warnings = app.health_check()
    assert any("shuffle straggler" in w and "partition 3" in w
               and "transport tier" in w for w in warnings), warnings
    assert any("retrie" in w and "backpressure" in w
               for w in warnings), warnings
    # balanced + retry-free: no shuffle warnings
    app = load_event_log(_v12_log(tmp_path / "ok.jsonl", "ok", _summary()))
    assert not [w for w in app.health_check()
                if "shuffle" in w.lower()]


def test_diagnose_straggler_and_backpressure_findings(tmp_path):
    from spark_rapids_tpu.tools.diagnose import diagnose_path
    report = diagnose_path(_v12_log(
        tmp_path / "sh.jsonl", "sh",
        _summary(retries=2, skew=8.0, depth=4)))
    (qd,) = report.queries
    metrics = {f.metric: f for f in qd.findings}
    assert "shuffleStraggler" in metrics
    assert "repartition" in metrics["shuffleStraggler"].suggestion
    assert "shuffleBackpressure" in metrics
    assert "backpressure" in metrics["shuffleBackpressure"].suggestion


def test_compare_shuffle_gate(tmp_path):
    from spark_rapids_tpu.tools.compare import compare_apps, shuffle_delta
    # unit: +5% is clean, +50% past the floors flags both keys
    base = {"shuffle_wall_s": 1.0, "wire_bytes": 10 << 20}
    _d, flagged = shuffle_delta(base, {"shuffle_wall_s": 1.04,
                                       "wire_bytes": 10 << 20})
    assert not flagged
    deltas, flagged = shuffle_delta(base, {"shuffle_wall_s": 1.5,
                                           "wire_bytes": 15 << 20})
    assert set(flagged) == {"shuffle_wall_s", "wire_bytes"}
    assert deltas["wire_bytes"] == 5 << 20
    assert shuffle_delta(None, base) == ({}, [])
    # end to end: a regressed run flags in compare_apps + the summary
    a = _v12_log(tmp_path / "a.jsonl", "a", _summary(wall=0.2))
    b = _v12_log(tmp_path / "b.jsonl", "b",
                 _summary(wall=0.5, wire=12 << 20))
    from spark_rapids_tpu.tools.eventlog import load_event_log
    report = compare_apps(load_event_log(a), load_event_log(b))
    assert report.shuffle_regressions()
    assert "SHUFFLE REGRESSION" in report.summary()
    clean = compare_apps(load_event_log(a), load_event_log(a))
    assert not clean.shuffle_regressions()


def test_sentinel_shuffle_wall_gate(tmp_path):
    """Two synthetic runs whose only difference is shuffle-wall growth
    past the 10% + 50ms gate: the sentinel flags shuffle_wall."""
    from spark_rapids_tpu.tools.history import (HistoryStore,
                                                SHUFFLE_WALL_KEY,
                                                run_sentinel)

    def _run(name, wall):
        return _v12_log(tmp_path / f"{name}.jsonl", name,
                        _summary(wall=wall),
                        stats={SHUFFLE_WALL_KEY: wall})

    store = HistoryStore(str(tmp_path / "store"))
    store.append_run(_run("run_a", 1.0), app_id="run_a")
    store.append_run(_run("run_b", 2.0), app_id="run_b")
    verdict = run_sentinel(store, candidate="run_b", baseline="run_a")
    assert not verdict["ok"]
    assert "shuffle_wall" in verdict["flags"]
    assert verdict["shuffle_wall_regressions"][0]["delta"] \
        == pytest.approx(1.0)
    # +4% under the relative gate: clean
    store.append_run(_run("run_c", 1.04), app_id="run_c")
    verdict = run_sentinel(store, candidate="run_c", baseline="run_a")
    assert verdict["ok"] and "shuffle_wall" not in verdict["flags"]


# ---------------------------------------------------------------------------
# 8-virtual-device mesh: the ICI collective tier (heavy -> slow tier)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_mesh_q3_ici_tier_observed_and_reconciles(tmp_path):
    """q3 on the 8-device virtual mesh: the exchange lowers to the ICI
    all-to-all and the observatory's ici-tier enqueue bytes reconcile
    exactly with the shuffleBytes metric while the dispatch wall is
    real (the MULTICHIP trajectory measurement, in miniature)."""
    from spark_rapids_tpu.parallel.mesh import virtual_cpu_mesh
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools import tpch
    from spark_rapids_tpu.tools.eventlog import load_event_log
    logdir = str(tmp_path / "evl")
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": logdir,
        "spark.rapids.tpu.shuffle.telemetry.enabled": True,
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 4,
        "spark.rapids.tpu.aqe.enabled": False,
        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1,
    })
    sess.attach_mesh(virtual_cpu_mesh(8))
    tables = tpch.gen_all(0, tiny=True)
    dfs = tpch.build_dataframes(sess, tables, num_partitions=2)
    out = tpch.q3(dfs).collect(device=True)
    assert out.num_rows > 0
    sess.close()
    telemetry.reset_shuffle_telemetry()
    (path,) = glob.glob(os.path.join(logdir, "*.jsonl"))
    (q,) = load_event_log(path).queries.values()
    sh = q.shuffle_summary
    ici = [t for t in sh["tiers"] if t["tier"] == "ici"]
    assert ici, f"no ici tier in {[t['tier'] for t in sh['tiers']]}"
    assert ici[0]["phases"].get("dispatch", 0.0) > 0
    assert ici[0]["wire_bytes"] > 0
    metric = sum(n.get("metrics", {}).get("shuffleBytes", 0)
                 for n in q.nodes)
    assert metric > 0
    assert sum(t["logical_bytes"] for t in sh["tiers"]) == metric
