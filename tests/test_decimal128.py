"""Differential tests for the decimal128 device kernels: every operation is
checked bit-exactly against arbitrary-precision python ints / Decimal
(the reference validates its DECIMAL_128 tier against Spark's BigDecimal —
decimalExpressions.scala, DecimalUtil.scala)."""
import decimal

import numpy as np
import pytest

from spark_rapids_tpu.expr import decimal128 as d128

import jax.numpy as jnp


def _rand_ints(rng, n, bits):
    out = np.empty(n, dtype=object)
    for i in range(n):
        b = int(rng.integers(0, bits))
        v = int(rng.integers(0, 2 ** 62)) | (int(rng.integers(0, 2 ** 62)) << 62)
        v &= (1 << b) - 1 if b else 0
        out[i] = -v if rng.random() < 0.5 else v
    # pin edge cases
    edges = [0, 1, -1, 2 ** 63 - 1, -2 ** 63, 2 ** 64, -(2 ** 64),
             10 ** 18, -(10 ** 18), 10 ** 37, -(10 ** 37),
             (1 << 126) - 1, -((1 << 126) - 1)]
    out[:len(edges)] = edges[:len(out)]
    return out


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def test_limb_roundtrip(rng):
    vals = _rand_ints(rng, 64, 126)
    limbs = d128.limbs_from_py_ints(vals, 64)
    back = d128.limbs_to_py_ints(limbs)
    # limbs_to_py_ints returns the unsigned composition; compare mod 2^128
    for v, b in zip(vals, back):
        assert (int(b) - int(v)) % (1 << 128) == 0


def _to_dev(vals):
    return jnp.asarray(d128.limbs_from_py_ints(vals, len(vals)))


def _signed(limbs):
    out = d128.limbs_to_py_ints(np.asarray(limbs))
    res = []
    for v in out:
        v = int(v) % (1 << 128)
        res.append(v - (1 << 128) if v >= (1 << 127) else v)
    return res


def test_add_sub_neg(rng):
    a = _rand_ints(rng, 128, 126)
    b = _rand_ints(rng, 128, 126)
    da, db = _to_dev(a), _to_dev(b)
    got_add = _signed(d128.d128_add(da, db))
    got_sub = _signed(d128.d128_sub(da, db))
    got_neg = _signed(d128.d128_neg(da))
    for i in range(128):
        m = 1 << 128

        def wrap(v):
            v %= m
            return v - m if v >= (1 << 127) else v
        assert got_add[i] == wrap(int(a[i]) + int(b[i])), i
        assert got_sub[i] == wrap(int(a[i]) - int(b[i])), i
        assert got_neg[i] == wrap(-int(a[i])), i


def test_cmp_eq_lt_sign_abs(rng):
    a = _rand_ints(rng, 128, 126)
    b = _rand_ints(rng, 128, 126)
    b[:16] = a[:16]  # equal pairs
    da, db = _to_dev(a), _to_dev(b)
    cmp = np.asarray(d128.d128_cmp(da, db))
    eq = np.asarray(d128.d128_eq(da, db))
    lt = np.asarray(d128.d128_lt(da, db))
    sign = np.asarray(d128.d128_sign(da))
    ab = _signed(d128.d128_abs(da))
    for i in range(128):
        x, y = int(a[i]), int(b[i])
        assert cmp[i] == (-1 if x < y else (1 if x > y else 0)), i
        assert eq[i] == (x == y), i
        assert lt[i] == (x < y), i
        assert sign[i] == (0 if x == 0 else (1 if x > 0 else -1)), i
        assert ab[i] == abs(x), i


def test_key_words_order(rng):
    a = _rand_ints(rng, 200, 126)
    da = _to_dev(a)
    w = d128.d128_key_words(da)
    keys = list(zip(np.asarray(w[0]).tolist(), np.asarray(w[1]).tolist()))
    order_words = sorted(range(200), key=lambda i: keys[i])
    order_true = sorted(range(200), key=lambda i: int(a[i]))
    assert [int(a[i]) for i in order_words] == [int(a[i]) for i in order_true]


def test_mul_rescaled_exact(rng):
    # decimal(38,*) x decimal(38,*) with scale drops, vs python Decimal
    for bits_a, bits_b, drop in [(60, 60, 0), (80, 40, 6), (100, 20, 10),
                                 (120, 6, 18), (63, 63, 4)]:
        a = _rand_ints(rng, 64, bits_a)
        b = _rand_ints(rng, 64, bits_b)
        da, db = _to_dev(a), _to_dev(b)
        limbs, over = d128.d128_mul_rescaled(da, db, drop, 38)
        got = _signed(limbs)
        overflow = np.asarray(over)
        for i in range(64):
            prod = int(a[i]) * int(b[i])
            q, r = divmod(abs(prod), 10 ** drop) if drop else (abs(prod), 0)
            if 2 * r >= 10 ** drop and drop:
                q += 1
            expect = -q if prod < 0 else q
            if abs(expect) >= 10 ** 38:
                assert overflow[i], (i, expect)
            else:
                assert not overflow[i], (i, expect, got[i])
                assert got[i] == expect, (i, bits_a, bits_b, drop)


def test_rescale_up_down(rng):
    a = _rand_ints(rng, 64, 90)
    da = _to_dev(a)
    up, over_u = d128.d128_rescale(da, 2, 6, 38)
    got_u = _signed(up)
    for i in range(64):
        expect = int(a[i]) * 10 ** 4
        if abs(expect) >= 10 ** 38:
            assert np.asarray(over_u)[i]
        else:
            assert got_u[i] == expect, i
    down, over_d = d128.d128_rescale(da, 6, 2, 38)
    got_d = _signed(down)
    for i in range(64):
        v = int(a[i])
        q, r = divmod(abs(v), 10 ** 4)
        if 2 * r >= 10 ** 4:
            q += 1
        expect = -q if v < 0 else q
        assert got_d[i] == expect, i
        assert not np.asarray(over_d)[i]


def test_round_half_up_exact_half():
    # exact .5 boundaries round AWAY from zero (BigDecimal HALF_UP)
    vals = np.array([15, -15, 25, -25, 5, -5, 149, -149, 150, -150],
                    dtype=object)
    da = _to_dev(vals)
    down, _ = d128.d128_rescale(da, 1, 0, 38)
    assert _signed(down) == [2, -2, 3, -3, 1, -1, 15, -15, 15, -15]


def test_i64_f64_conversions(rng):
    a = np.array([0, 1, -1, 2 ** 63 - 1, -2 ** 63, 10 ** 18, -(10 ** 18)]
                 + [int(rng.integers(-2 ** 62, 2 ** 62)) for _ in range(57)],
                 dtype=object)
    da = jnp.asarray(np.array([int(v) for v in a], dtype=np.int64))
    limbs = d128.d128_from_i64(da)
    assert _signed(limbs) == [int(v) for v in a]
    back, over = d128.d128_to_i64(limbs)
    assert not np.asarray(over).any()
    assert np.asarray(back).tolist() == [int(v) for v in a]
    wide = _to_dev(np.array([2 ** 64 + 5, -(2 ** 64 + 5)], dtype=object))
    _, over_w = d128.d128_to_i64(wide)
    assert np.asarray(over_w).all()
    f = np.asarray(d128.d128_to_f64(_to_dev(np.array([10 ** 30, -(10 ** 30)],
                                                     dtype=object))))
    assert f[0] == pytest.approx(1e30, rel=1e-12)
    assert f[1] == pytest.approx(-1e30, rel=1e-12)
    fl, over_f = d128.d128_from_f64(jnp.asarray(np.array([1e30, -1e30, 1e40])))
    assert _signed(fl)[0] == pytest.approx(10 ** 30, rel=1e-12)
    assert np.asarray(over_f).tolist() == [False, False, True]


def test_overflow_flag(rng):
    vals = np.array([10 ** 38 - 1, -(10 ** 38 - 1), 10 ** 38, -(10 ** 38)],
                    dtype=object)
    over = np.asarray(d128.d128_overflows(_to_dev(vals), 38))
    assert over.tolist() == [False, False, True, True]


def test_segment_sum(rng):
    n, cap = 256, 16
    vals = _rand_ints(rng, n, 120)
    gid = rng.integers(0, cap, n)
    contrib = rng.random(n) < 0.8
    limbs, over = d128.d128_segment_sum(
        _to_dev(vals), jnp.asarray(contrib), jnp.asarray(gid), cap, 38)
    got = _signed(limbs)
    overflow = np.asarray(over)
    for g in range(cap):
        expect = sum(int(v) for v, gi, c in zip(vals, gid, contrib)
                     if gi == g and c)
        if abs(expect) >= 10 ** 38:
            assert overflow[g], g
        else:
            assert not overflow[g], (g, expect, got[g])
            assert got[g] == expect, g
